"""Paged-KV prefill / decode forward passes.

TPU-native redesign of the paged attention the reference delegates to
vLLM (``python/ray/llm/_internal/serve/deployments/llm/vllm/
vllm_engine.py:250``): the KV cache is a shared **page pool**

    k_pages / v_pages: [layers, num_pages, kv_heads, page_size, head_dim]

and each sequence owns an int32 **block table** of page indices. All shapes
are static — block tables are data, not shapes — so XLA compiles one
program per (chunk bucket) and one decode program total, while the
allocator moves pages between sequences at runtime (the property vLLM
gets from CUDA kernels, recovered here through gather/scatter that XLA
tiles natively).

Design points:
  * **Chunked prefill** (``prefill_chunk``): a prompt is processed in
    page-aligned chunks; each chunk attends over the pages written so far
    plus itself (causal), then scatters its K/V into the pool. Bounded
    chunk size keeps decode TTFT for other requests bounded — the
    reference's chunked-prefill scheduling.
  * **Prefix reuse**: a prompt whose leading blocks hash-match cached
    pages skips them entirely — the block table points at the shared
    pages read-only (engine-side trie + refcounting), and a partial
    tail-block match starts the suffix MID-page: the engine COW-forks
    the shared page first (``copy_pages``) and the chunk's row-granular
    ``(page, offset)`` scatter writes past the copied rows.
  * **Decode** (``decode_step``): one batched step over all slots;
    context K/V is read per-slot via the block tables. Inactive slots
    point at a per-slot trash page so their (ignored) writes never
    corrupt live pages — branchless, one compiled program for every
    occupancy.
  * **Paged v2 staging schedule** (``decode_loop(paged=True)``): the
    page pool is STRICTLY READ-ONLY across the whole K-step fused
    dispatch — the Pallas kernel only ever reads it, so XLA inserts no
    pool-sized copies around the custom call. Tokens generated inside
    the dispatch accumulate in a small ``[L, slots, KH, SC, D]`` staging
    carry (KBs, not GBs) the kernel folds into its online softmax as a
    second KV source, and ``commit_staging`` writes them back with ONE
    batched scatter at the dispatch boundary.
  * **The pool rides the layer scan as CARRY, never as scan xs.** The
    stacked pool is donated and updated in place layer by layer
    (``pool.at[l, pages, ...]``); gathers index ``pool[l, tables]``
    directly. Slicing the pool per layer as scan xs/ys (the obvious
    structure) makes XLA materialize pool-sized copies every layer of
    every step — measured ~45% of decode wall time at 2k capacity and
    ~3x total at 8k. Pool touches must stay at page granularity.
  * **Capacity-independent cost.** ``live_pages`` (a static,
    host-computed, power-of-two-bucketed bound on any slot's live page
    count) caps the attention width — gather or kernel grid — so a
    200-token batch costs the same under a 2k and an 8k ``max_len``.

Invariant (same as the reference's page model): before any step at
position ``pos``, pages hold K/V for ``[0, pos)``; the step writes
``pos`` and attends over ``[0, pos]``; garbage beyond ``pos`` is masked.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from ..models.llama import LlamaConfig
from ..ops import apply_rope, rms_norm
from ..ops.paged_attention import paged_decode_attention, stage_rows


def init_pages(config: LlamaConfig, num_pages: int, page_size: int) -> dict:
    c = config
    shape = (c.n_layers, num_pages, c.n_kv_heads, page_size, c.head_dim)
    return {"k": jnp.zeros(shape, c.dtype), "v": jnp.zeros(shape, c.dtype)}


def _project_qkv(h, layer):
    q = jnp.einsum("bse,ehd->bhsd", h, layer["wq"])
    k = jnp.einsum("bse,ehd->bhsd", h, layer["wk"])
    v = jnp.einsum("bse,ehd->bhsd", h, layer["wv"])
    return q, k, v


def _mlp(x, layer, c: LlamaConfig, tp_axis: str | None = None):
    """SwiGLU MLP. ``tp_axis`` names a MANUAL mesh axis the mlp dim is
    sharded over (the flattened pp×tp region in ``pp_model``): gate/up
    are column-parallel (local), down is row-parallel — its partial
    output psums over the axis before rejoining the replicated residual.
    ``None`` (every auto-partitioned caller) is the unchanged path."""
    h = rms_norm(x, layer["mlp_norm"], eps=c.norm_eps)
    gate = jnp.einsum("bse,em->bsm", h, layer["w_gate"])
    up = jnp.einsum("bse,em->bsm", h, layer["w_up"])
    ff = jax.nn.silu(gate.astype(jnp.float32)).astype(c.dtype) * up
    down = jnp.einsum("bsm,me->bse", ff, layer["w_down"])
    if tp_axis is not None:
        down = lax.psum(down, tp_axis)
    return x + down


def _gather_ctx(pool, l, tables):
    """Layer-indexed page gather: pool [L, P, KH, page, D], tables
    [..., B] int32 -> [..., KH, B*page, D]. One gather op — the [P, ...]
    layer slice is never materialized."""
    g = pool[l, tables]                        # [..., B, KH, page, D]
    g = jnp.swapaxes(g, -4, -3)                # [..., KH, B, page, D]
    return g.reshape(*g.shape[:-3], -1, g.shape[-1])


@functools.partial(jax.jit,
                   static_argnames=("config", "page_size", "live_pages"),
                   donate_argnames=("pages",))
def prefill_chunk(params, pages: dict, block_table, tokens, start_pos,
                  config: LlamaConfig, page_size: int,
                  live_pages: int | None = None, lora=None, lora_slot=None):
    """Process one prompt chunk.

    tokens:      [C] int32 (static bucket size).
    block_table: [max_pages_per_seq] int32 — this sequence's pages.
    start_pos:   scalar int32. NOT required to be page-aligned: a
                 prefix-cache partial-block hit starts the suffix
                 mid-page (the engine COW-forks the shared page first),
                 so K/V lands via a row-granular (page, offset) scatter —
                 identical destinations to the old page-granular write
                 when the start IS aligned.
    live_pages:  static host-computed bound ≥ ``ceil(start_pos / page)``
                 — caps the context-gather width so chunk cost scales
                 with written context, not pool capacity.

    Attends over previously-written context ``[0, start_pos)`` (gathered
    via the block table; partial-page context rows are masked by
    position, so a mid-page start reads exactly the valid prefix rows)
    plus the chunk itself (causal), writes the chunk's K/V into its
    pages, and returns (pages, hidden [C, E]).
    """
    c = config
    C = tokens.shape[0]
    positions = start_pos + jnp.arange(C, dtype=jnp.int32)
    gather_table = block_table
    if live_pages is not None and live_pages < block_table.shape[0]:
        gather_table = block_table[:live_pages]
    max_ctx = gather_table.shape[0] * page_size
    ctx_pos = jnp.arange(max_ctx, dtype=jnp.int32)
    ctx_live = ctx_pos < start_pos                      # [ctx]
    causal = jnp.arange(C)[:, None] >= jnp.arange(C)[None, :]
    kh, g = c.n_kv_heads, c.n_heads // c.n_kv_heads
    # Row-granular write destinations: position p -> (its page, offset).
    # The clamp keeps pad rows past the table in range; they land at
    # future offsets of the last page, are masked (position > pos) until
    # decode overwrites them, and the engine clamps chunks so real
    # positions never exceed the table.
    write_pages = block_table[jnp.minimum(positions // page_size,
                                          block_table.shape[0] - 1)]  # [C]
    write_offs = positions % page_size                                # [C]

    x0 = params["embed"][tokens][None].astype(c.dtype)   # [1, C, E]

    def body(carry, xs):
        x, kf, vf = carry
        layer, l = xs
        h = rms_norm(x, layer["attn_norm"], eps=c.norm_eps)
        q, k, v = _project_qkv(h, layer)                # [1, H|KH, C, D]
        if lora is not None:
            # Prompt K/V must carry the adapter too (one adapter per
            # sequence — chunked prefill is single-sequence).
            from .lora import lora_delta_single

            def add(t, p, heads):
                d = lora_delta_single(h, lora[f"{p}.A"], lora[f"{p}.B"],
                                      l, lora_slot)
                return t + jnp.swapaxes(
                    d.reshape(1, C, heads, c.head_dim), 1, 2).astype(t.dtype)

            q = add(q, "wq", c.n_heads)
            k = add(k, "wk", c.n_kv_heads)
            v = add(v, "wv", c.n_kv_heads)
        q = apply_rope(q, positions, theta=c.rope_theta)
        k = apply_rope(k, positions, theta=c.rope_theta)
        ck = _gather_ctx(kf, l, gather_table)           # [KH, ctx, D]
        cv = _gather_ctx(vf, l, gather_table)
        qg = q[0].reshape(kh, g, C, c.head_dim)
        # context scores [KH, G, C, ctx] + in-chunk causal scores [.., C]
        s_ctx = jnp.einsum("kgcd,ktd->kgct", qg, ck).astype(jnp.float32)
        s_self = jnp.einsum("kgcd,ktd->kgct", qg, k[0]).astype(jnp.float32)
        scale = c.head_dim ** -0.5
        s_ctx = jnp.where(ctx_live[None, None, None], s_ctx * scale, -jnp.inf)
        s_self = jnp.where(causal[None, None], s_self * scale, -jnp.inf)
        probs = jax.nn.softmax(jnp.concatenate([s_ctx, s_self], axis=-1), axis=-1)
        p_ctx, p_self = probs[..., :max_ctx].astype(c.dtype), probs[..., max_ctx:].astype(c.dtype)
        attn = jnp.einsum("kgct,ktd->kgcd", p_ctx, cv) + jnp.einsum(
            "kgct,ktd->kgcd", p_self, v[0])
        attn = attn.reshape(1, c.n_heads, C, c.head_dim)
        out = jnp.einsum("bhsd,hde->bse", attn, layer["wo"])
        if lora is not None:
            from .lora import lora_delta_single

            flat = jnp.swapaxes(attn, 1, 2).reshape(1, C, -1)
            out = out + lora_delta_single(
                flat, lora["wo.A"], lora["wo.B"], l, lora_slot).astype(out.dtype)
        x2 = _mlp(x + out, layer, c)
        # Row-granular scatter of the chunk's K/V: row j -> (page of
        # position start+j, its offset). Distinct in-range positions give
        # distinct (page, offset) pairs — no conflicts — and unlike the
        # old whole-page write this supports a mid-page chunk start
        # without clobbering a COW fork's copied prefix rows.
        kf = kf.at[l, write_pages, :, write_offs, :].set(
            jnp.swapaxes(k[0], 0, 1))
        vf = vf.at[l, write_pages, :, write_offs, :].set(
            jnp.swapaxes(v[0], 0, 1))
        return (x2, kf, vf), None

    (x, new_k, new_v), _ = lax.scan(
        body, (x0, pages["k"], pages["v"]),
        (params["layers"], jnp.arange(c.n_layers)))
    hidden = rms_norm(x, params["final_norm"], eps=c.norm_eps)[0]  # [C, E]
    return {"k": new_k, "v": new_v}, hidden


def decode_block(x, layer, kf, vf, l, block_tables, pos, write_idx,
                 c: LlamaConfig, page_size: int, paged: bool = False,
                 live_pages: int | None = None, lora=None, lora_idx=None,
                 stage=None, stage_step=None, stage_live=None,
                 attn_mesh=None, tp_axis: str | None = None):
    """One decoder block for a [n, 1, E] single-token batch against the
    FULL page pool (kf/vf: [L, P, KH, page, D]; ``l`` is this layer's
    index into it — traced, so the pool is only touched at gather/scatter
    granularity and updates stay in place). Shared by the unpipelined
    decode (``_decode_logits``) and the pp pipeline (``pp_model``) so the
    two paths stay bitwise-identical (greedy parity depends on it).
    Returns ``(x2, kf, vf, stage)``.

    ``paged=True`` routes context attention through the Pallas
    paged-attention kernel (``ops/paged_attention.py``): HBM traffic per
    step proportional to each slot's LIVE context. The v2 staging-buffer
    contract keeps the pool STRICTLY READ-ONLY around the kernel:

      * With ``stage=(k_stage, v_stage)`` (the fused decode loop) this
        layer's fresh K/V lands in staging row ``stage_step`` at layer
        ``l`` and the kernel folds rows [0, stage_step] as a second KV
        source; the pool is untouched — ``decode_loop`` commits the whole
        staging buffer with ONE batched scatter at the dispatch boundary.
      * Without ``stage`` (single-step ``decode_step``) the fresh K/V
        rides the kernel's compat path (``k_cur``/``v_cur``) and is
        scattered into the pool AFTER the kernel call — the pool is never
        simultaneously a kernel operand and a write target, so the
        donated buffer updates in place with no defensive copies.

    ``paged=False`` is the dense gather — width capped by ``live_pages``
    — kept as the CPU/test default and the numerical ground truth.
    ``attn_mesh`` (static) shard_maps the kernel over the mesh's tp axis
    (KV heads). ``tp_axis`` instead names a tp axis this block is ALREADY
    manual over (the flattened pp×tp region in ``pp_model``): the head
    dims of q/k/v/pool arrive pre-sharded, attention runs on the local
    heads with no collective, and the row-parallel ``wo`` output psums
    over the axis — so the KV-head count is read from the pool shard,
    never from the (global) config."""
    n = x.shape[0]
    # Local KV heads from the pool shard (== c.n_kv_heads everywhere
    # except inside a manual-tp region); the GQA ratio is tp-invariant.
    kh, g = kf.shape[2], c.n_heads // c.n_kv_heads
    offset = pos % page_size
    h = rms_norm(x, layer["attn_norm"], eps=c.norm_eps)
    q, k, v = _project_qkv(h, layer)                   # [n, H|KH, 1, D]
    if lora is not None:
        # Per-slot LoRA deltas on the attention projections (pre-rope):
        # each batch row gathers its adapter's A/B from the device stack
        # — batched multi-adapter decode in one compiled program (the
        # capability the reference buys from vLLM's SGMV kernels).
        from .lora import lora_delta

        def add(t, p, heads):
            d = lora_delta(h, lora[f"{p}.A"], lora[f"{p}.B"], l, lora_idx)
            return t + jnp.swapaxes(
                d.reshape(n, 1, heads, c.head_dim), 1, 2).astype(t.dtype)

        q = add(q, "wq", c.n_heads)
        k = add(k, "wk", c.n_kv_heads)
        v = add(v, "wv", c.n_kv_heads)
    q = apply_rope(q, pos[:, None], theta=c.rope_theta)
    k = apply_rope(k, pos[:, None], theta=c.rope_theta)
    qg = q[:, :, 0].reshape(n, kh, g, c.head_dim)
    if paged:
        k_tok, v_tok = k[:, :, 0], v[:, :, 0]            # [n, KH, D]
        if stage is not None:
            ks, vs = stage
            k_row, v_row = k_tok.astype(ks.dtype), v_tok.astype(vs.dtype)
            if stage_live is not None:
                # Pipeline warmup/cooldown ticks compute garbage rows
                # (pp_model): a guarded write keeps the round's REAL
                # staged K/V intact for the dispatch-boundary commit.
                k_row = jnp.where(stage_live, k_row, ks[l, :, :, stage_step])
                v_row = jnp.where(stage_live, v_row, vs[l, :, :, stage_step])
            ks = ks.at[l, :, :, stage_step].set(k_row)
            vs = vs.at[l, :, :, stage_step].set(v_row)
            attn = paged_decode_attention(
                qg, kf, vf, block_tables, pos,
                page_size=page_size, live_pages=live_pages, layer=l,
                k_stage=ks, v_stage=vs, stage_idx=stage_step,
                mesh=attn_mesh)
            stage = (ks, vs)
        else:
            attn = paged_decode_attention(
                qg, kf, vf, block_tables, pos, k_tok, v_tok,
                page_size=page_size, live_pages=live_pages, layer=l,
                mesh=attn_mesh)
            # Commit AFTER the read-only kernel: same per-step scatter
            # cost as the dense path, in place on the donated pool.
            kf = kf.at[l, write_idx, :, offset, :].set(k_tok)
            vf = vf.at[l, write_idx, :, offset, :].set(v_tok)
        attn = attn.reshape(n, 1, kh * g * c.head_dim)
    else:
        # Write each slot's new K/V at (its current page, offset), then
        # attend over the gathered context [0, pos]. Distinct slots own
        # distinct pages (trash pages for inactive slots), so the
        # scatter has no conflicting indices.
        kf = kf.at[l, write_idx, :, offset, :].set(k[:, :, 0])
        vf = vf.at[l, write_idx, :, offset, :].set(v[:, :, 0])
        if live_pages is not None and live_pages < block_tables.shape[1]:
            block_tables = block_tables[:, :live_pages]
        max_ctx = block_tables.shape[1] * page_size
        live = jnp.arange(max_ctx)[None] <= pos[:, None]   # [n, ctx]
        ck = _gather_ctx(kf, l, block_tables)          # [n, KH, ctx, D]
        cv = _gather_ctx(vf, l, block_tables)
        scores = jnp.einsum("nkgd,nktd->nkgt", qg, ck).astype(jnp.float32)
        scores *= c.head_dim ** -0.5
        scores = jnp.where(live[:, None, None], scores, -jnp.inf)
        probs = jax.nn.softmax(scores, axis=-1).astype(c.dtype)
        attn = jnp.einsum("nkgt,nktd->nkgd", probs, cv).reshape(
            n, 1, kh * g * c.head_dim)
    # reshape(-1, hidden): wo's head axis may be a LOCAL tp shard.
    out = jnp.einsum("bsf,fe->bse", attn, layer["wo"].reshape(-1, c.hidden))
    if lora is not None:
        from .lora import lora_delta

        out = out + lora_delta(attn, lora["wo.A"], lora["wo.B"],
                               l, lora_idx).astype(out.dtype)
    if tp_axis is not None:
        # Row-parallel wo: each shard's local-head contribution is a
        # partial sum over the (sharded) head axis.
        out = lax.psum(out, tp_axis)
    return _mlp(x + out, layer, c, tp_axis=tp_axis), kf, vf, stage


def _decode_logits(params, pages: dict, block_tables, tokens, pos,
                   config: LlamaConfig, page_size: int, write_page_idx=None,
                   paged: bool = False, live_pages: int | None = None,
                   lora=None, lora_idx=None, stage=None, stage_step=None,
                   attn_mesh=None):
    """One batched decode step over all slots.

    block_tables: [slots, max_pages_per_seq] int32 (inactive slots must
                  point at their private trash page).
    tokens:       [slots] int32 — token at ``pos[i]`` of each sequence.
    pos:          [slots] int32 — write/attend position.
    write_page_idx: optional [slots] override of the page each slot writes
                  to (the multi-step loop redirects finished slots to
                  their trash page).
    stage/stage_step: paged-v2 staging carry — see ``decode_block``. With
                  staging, the pool comes back UNTOUCHED and the fresh
                  K/V rides the returned stage buffers; the caller owns
                  the dispatch-boundary commit (``commit_staging``).
    Returns (logits [slots, vocab] f32, new pages, stage).
    """
    c = config
    x = params["embed"][tokens][:, None].astype(c.dtype)   # [slots, 1, E]
    if write_page_idx is None:
        write_page_idx = jnp.take_along_axis(
            block_tables, (pos // page_size)[:, None], axis=1)[:, 0]  # [slots]
    page_idx = write_page_idx

    def body(carry, xs):
        x, kf, vf, stg = carry
        layer, l = xs
        x2, kf, vf, stg = decode_block(
            x, layer, kf, vf, l, block_tables, pos, page_idx, c, page_size,
            paged=paged, live_pages=live_pages, lora=lora, lora_idx=lora_idx,
            stage=stg, stage_step=stage_step, attn_mesh=attn_mesh)
        return (x2, kf, vf, stg), None

    (x, new_k, new_v, stage), _ = lax.scan(
        body, (x, pages["k"], pages["v"], stage),
        (params["layers"], jnp.arange(c.n_layers)))
    hidden = rms_norm(x, params["final_norm"], eps=c.norm_eps)     # [slots, 1, E]
    logits = jnp.einsum("bse,ev->bsv", hidden, params["lm_head"])[:, 0]
    return logits.astype(jnp.float32), {"k": new_k, "v": new_v}, stage


def commit_staging(pages: dict, stage, write_idx_steps, pos0, n_steps: int,
                   page_size: int):
    """Dispatch-boundary commit: ONE batched scatter folds the staging
    buffer back into the (donated, read-only-until-now) page pool.

    stage:           (k_stage, v_stage) [L, slots, KH, SC, D] — row j of
                     slot s holds the roped K/V of position pos0_s + j.
    write_idx_steps: [n_steps, slots] int32 — the page each slot wrote at
                     each fused step (trash pages for finished slots),
                     recorded by the decode scan.
    pos0:            [slots] int32 — each slot's position at dispatch
                     start (the pool held [0, pos0) throughout).

    By the time this scatter runs the scan that READ the pool has
    completed, so XLA updates the donated buffer in place — the whole
    point of the v2 design: zero pool-sized copies per dispatch.
    """
    k_stage, v_stage = stage
    L, n, kh, _, d = k_stage.shape
    steps = jnp.arange(n_steps, dtype=jnp.int32)
    off = ((pos0[None, :] + steps[:, None]) % page_size).reshape(-1)  # [K*S]
    widx = write_idx_steps.reshape(-1)                                # [K*S]

    def rows(s):
        # [L, S, KH, SC, D] -> staged rows [K*S, L, KH, D] in (step, slot)
        # order matching ``widx``/``off``.
        r = jnp.transpose(s[:, :, :, :n_steps], (3, 1, 0, 2, 4))
        return r.reshape(n_steps * n, L, kh, d)

    new_k = pages["k"].at[:, widx, :, off, :].set(
        rows(k_stage).astype(pages["k"].dtype))
    new_v = pages["v"].at[:, widx, :, off, :].set(
        rows(v_stage).astype(pages["v"].dtype))
    return {"k": new_k, "v": new_v}


@functools.partial(jax.jit, donate_argnames=("pages",))
def copy_pages(pages: dict, src, dst):
    """Copy-on-write fork: duplicate pages ``src`` into pages ``dst``
    across every layer (one gather + one scatter on the donated pool —
    page-granular, never pool-sized). The engine calls this when a slot
    is about to WRITE into a shared prefix page: the fork gets the
    shared page's rows, the slot's table swaps to the fork, and the
    shared original stays immutable for its other readers.

    src/dst: [m] int32 page ids (m is tiny — usually 1).
    """
    return {"k": pages["k"].at[:, dst].set(pages["k"][:, src]),
            "v": pages["v"].at[:, dst].set(pages["v"][:, src])}


@functools.partial(jax.jit, donate_argnames=("pages",))
def write_pages(pages: dict, dst, k_rows, v_rows):
    """KV-migration import: scatter transferred page contents into pages
    ``dst`` across every layer — the inverse of the export gather, and
    the device half of ``import_pages``. Like ``copy_pages`` this is
    page-granular on the donated pool (one scatter, never pool-sized),
    and the destination pages are freshly reserved by the allocator, so
    the write can never alias a live sequence's pages.

    dst: [m] int32 page ids; k_rows/v_rows: [L, m, KH, page, D] host
    arrays (the wire format of a migration chunk).
    """
    return {"k": pages["k"].at[:, dst].set(k_rows.astype(pages["k"].dtype)),
            "v": pages["v"].at[:, dst].set(v_rows.astype(pages["v"].dtype))}


@functools.wraps(_decode_logits)
def _decode_step(*args, **kwargs):
    logits, pages, _ = _decode_logits(*args, **kwargs)
    return logits, pages


decode_step = functools.partial(
    jax.jit,
    static_argnames=("config", "page_size", "paged", "live_pages",
                     "attn_mesh"),
    donate_argnames=("pages",)
)(_decode_step)


@functools.partial(
    jax.jit,
    static_argnames=("config", "page_size", "paged", "live_pages",
                     "attn_mesh"),
    donate_argnames=("pages",))
def decode_and_sample(params, pages: dict, block_tables, tokens, pos, temps, key,
                      config: LlamaConfig, page_size: int, paged: bool = False,
                      live_pages: int | None = None, lora=None, lora_idx=None,
                      attn_mesh=None):
    """``decode_step`` + on-device sampling in ONE compiled program.

    The engine drives the chip through a (possibly remote) dispatch
    channel where every op launch and transfer costs real latency; doing
    argmax/categorical host-side meant ~6 dispatches and a [slots, vocab]
    f32 logits pull PER TOKEN. Here sampling (greedy for temp<=0,
    tempered categorical otherwise) and the RNG split happen on device —
    one dispatch, and only [slots] int32 tokens cross back.
    """
    logits, new_pages, _ = _decode_logits(params, pages, block_tables, tokens,
                                          pos, config, page_size, paged=paged,
                                          live_pages=live_pages, lora=lora,
                                          lora_idx=lora_idx,
                                          attn_mesh=attn_mesh)
    key, sub = jax.random.split(key)
    greedy = jnp.argmax(logits, axis=-1)
    sampled = jax.random.categorical(sub, logits / jnp.maximum(temps, 1e-6)[:, None])
    out = jnp.where(temps > 0.0, sampled, greedy).astype(jnp.int32)
    return out, key, new_pages


@functools.partial(
    jax.jit,
    static_argnames=("config", "page_size", "n_steps", "paged", "live_pages",
                     "prefill_live_pages", "attn_mesh"),
    donate_argnames=("pages",))
def mixed_dispatch(params, pages: dict, prefill_ops, block_tables, tokens,
                   pos, temps, eos_ids, remaining, key, config: LlamaConfig,
                   page_size: int, n_steps: int, paged: bool = False,
                   live_pages: int | None = None,
                   prefill_live_pages: tuple = (),
                   lora=None, lora_idx=None, attn_mesh=None):
    """Token-budget mixed step: prefill chunk(s) AND the full-batch decode
    burst in ONE compiled program / ONE dispatch (Sarathi-style
    chunked-prefill scheduling: prefill rides along with decode instead of
    preempting it, so a long prompt can no longer head-of-line-block the
    running streams' inter-token latency).

    prefill_ops: static-length tuple of ``(block_table [max_pages],
        tokens [C_i], start_pos)`` — one page-aligned chunk per admitted
        prompt, each ``C_i`` a legacy chunk bucket so this program adds NO
        new prefill shapes, only combinations (the compile key is the
        tuple of bucket sizes × the decode ``live_pages`` bucket).
    prefill_live_pages: per-op static context bound (same bucketing as the
        standalone prefill path).

    The pool interaction is safe by construction: the prefilling
    sequences own disjoint pages from every decoding slot (the allocator
    hands out distinct pages; inactive slots write to private trash
    pages), so chunk scatters and the decode schedule never alias. On the
    paged path the decode scan still only READS the pool — the chunk
    scatters happen before it and ``commit_staging`` after, preserving
    the v2 no-pool-copies property.

    Returns ``(decode_tokens [n_steps, slots], key, pages,
    hiddens tuple)`` — one ``[C_i, E]`` hidden per prefill op, for
    first-token sampling of ops that finished their prompt.
    """
    hiddens = []
    for (p_bt, p_tokens, p_start), lp in zip(prefill_ops, prefill_live_pages):
        pages, hidden = prefill_chunk.__wrapped__(
            params, pages, p_bt, p_tokens, p_start,
            config=config, page_size=page_size, live_pages=lp)
        hiddens.append(hidden)
    toks, key, pages = decode_loop.__wrapped__(
        params, pages, block_tables, tokens, pos, temps, eos_ids, remaining,
        key, config=config, page_size=page_size, n_steps=n_steps, paged=paged,
        live_pages=live_pages, lora=lora, lora_idx=lora_idx,
        attn_mesh=attn_mesh)
    return toks, key, pages, tuple(hiddens)


@jax.jit
def sample_first_token(last_hidden, lm_head, temp, key):
    """First-token sampling after prefill, on device (one dispatch)."""
    logits = (last_hidden @ lm_head).astype(jnp.float32)
    key, sub = jax.random.split(key)
    greedy = jnp.argmax(logits)
    sampled = jax.random.categorical(sub, logits / jnp.maximum(temp, 1e-6))
    return jnp.where(temp > 0.0, sampled, greedy).astype(jnp.int32), key


@jax.jit
def sample_first_batch(hiddens, lm_head, temps, key):
    """Batched first-token sampling for several just-prefilled requests
    in ONE dispatch (the engine stacks pending prefills so a burst of
    arrivals costs one host sync total, not one per request).

    hiddens: [m, E] last-position hidden states (padded rows ignored).
    Returns (tokens [m] int32, key).
    """
    logits = (hiddens @ lm_head).astype(jnp.float32)   # [m, vocab]
    key, sub = jax.random.split(key)
    greedy = jnp.argmax(logits, axis=-1)
    sampled = jax.random.categorical(sub, logits / jnp.maximum(temps, 1e-6)[:, None])
    return jnp.where(temps > 0.0, sampled, greedy).astype(jnp.int32), key


@functools.partial(
    jax.jit,
    static_argnames=("config", "page_size", "n_steps", "paged", "live_pages",
                     "attn_mesh"),
    donate_argnames=("pages",))
def decode_loop(params, pages: dict, block_tables, tokens, pos, temps, eos_ids,
                remaining, key, config: LlamaConfig, page_size: int, n_steps: int,
                paged: bool = False, live_pages: int | None = None,
                lora=None, lora_idx=None, attn_mesh=None):
    """``n_steps`` decode+sample iterations in ONE dispatch (on-device
    ``lax.scan`` generate loop, JetStream-style).

    Per-token host syncs cost a full dispatch round trip — prohibitive
    over a remote-dispatch channel (~150 ms each here). Scanning K steps
    on device amortizes that to one sync per K tokens. Slots whose
    sequence finishes mid-scan (EOS hit, or ``remaining`` steps
    exhausted) keep computing branchlessly but redirect their KV writes
    to their private trash page, so they can never overrun their page
    allocation or corrupt shared prefix pages; the host discards their
    surplus tokens.

    ``paged=True`` runs the v2 staging-buffer schedule: the pool is
    STRICTLY READ-ONLY across all ``n_steps`` (nothing for XLA to copy
    around the opaque kernel), step ``j`` appends its fresh K/V to a
    small ``[L, slots, KH, SC, D]`` staging carry the kernel folds into
    its online softmax, and ``commit_staging`` writes everything back
    with ONE batched scatter after the scan.

    eos_ids:   [slots] int32 (-1 = no EOS for that slot).
    remaining: [slots] int32 — tokens the slot may still emit (bounds
               both max_new_tokens and the page allocation).
    live_pages: static bound on the attention width — for the dense path
               it must cover ``max(pos) + n_steps - 1`` (tokens land in
               the pool mid-dispatch); for paged it need only cover the
               POOL context ``max(pos)`` (fresher tokens ride staging).
    Returns (tokens [n_steps, slots] int32, key, pages).
    """
    n = tokens.shape[0]
    trash = jnp.arange(n, dtype=jnp.int32)  # slot i's trash page is page i
    stage0 = None
    if paged:
        sc = stage_rows(n_steps)
        shape = (config.n_layers, n, config.n_kv_heads, sc, config.head_dim)
        stage0 = (jnp.zeros(shape, pages["k"].dtype),
                  jnp.zeros(shape, pages["v"].dtype))

    def body(carry, j):
        tokens, cur, done, remaining, key, pages, stage = carry
        real_page = jnp.take_along_axis(
            block_tables,
            jnp.minimum(cur // page_size, block_tables.shape[1] - 1)[:, None],
            axis=1)[:, 0]
        write_idx = jnp.where(done, trash, real_page)
        logits, pages, stage = _decode_logits(
            params, pages, block_tables, tokens, cur, config, page_size,
            write_page_idx=write_idx, paged=paged, live_pages=live_pages,
            lora=lora, lora_idx=lora_idx, stage=stage,
            stage_step=j if paged else None, attn_mesh=attn_mesh)
        key, sub = jax.random.split(key)
        greedy = jnp.argmax(logits, axis=-1)
        sampled = jax.random.categorical(sub, logits / jnp.maximum(temps, 1e-6)[:, None])
        new_tok = jnp.where(temps > 0.0, sampled, greedy).astype(jnp.int32)
        remaining = remaining - jnp.where(done, 0, 1)
        done = done | (new_tok == eos_ids) | (remaining <= 0)
        return ((new_tok, cur + 1, done, remaining, key, pages, stage),
                (new_tok, write_idx))

    init = (tokens, pos, remaining <= 0, remaining, key, pages, stage0)
    ((_, _, _, _, key, pages, stage), (toks, widx)) = lax.scan(
        body, init, jnp.arange(n_steps, dtype=jnp.int32))
    if paged:
        # The one pool write of the whole dispatch — the scan above only
        # READ the pool, so the donated buffer updates in place here.
        pages = commit_staging(pages, stage, widx, pos, n_steps, page_size)
    return toks, key, pages


@functools.partial(
    jax.jit,
    static_argnames=("config", "page_size", "n_draft", "paged", "live_pages",
                     "attn_mesh"),
    donate_argnames=("pages",))
def verify_block(params, pages: dict, block_tables, tokens_mat, pos, temps,
                 eos_ids, remaining, key, config: LlamaConfig,
                 page_size: int, n_draft: int, paged: bool = False,
                 live_pages: int | None = None, attn_mesh=None):
    """Speculative verify: score all ``n_draft + 1`` positions of every
    slot's drafted continuation in ONE dispatch — the ``decode_and_sample``
    sibling the speculation stage rides.

    tokens_mat: [slots, S] int32, S = n_draft + 1 — column 0 is each
                slot's current token (the one plain decode would feed at
                ``pos``), columns 1..K its drafted continuation; -1 pads
                a short draft (auto-rejected, never emitted).
    pos:        [slots] int32 — the pool holds K/V for [0, pos) per slot
                (identical precondition to a plain decode step).

    The forward is a tiny batched prefill chunk: every slot's S tokens
    attend over its POOL context [0, pos) plus themselves (causal), so
    one model pass produces the target logits at all S positions. The
    chunk's K/V never touches the pool mid-pass — it accumulates in the
    v2 STAGING carry (the decode_loop machinery, [L, slots, KH, SC, D]),
    the paged kernel folds staged rows [0, j] as position j's second KV
    source, and the dense path masks the pool gather strictly below
    ``pos``. Acceptance then runs on device:

      * greedy (temp <= 0): position j's output is ``argmax(p_j)``;
        draft j+1 is accepted iff it EQUALS that argmax — so every
        emitted token is the argmax the plain decode path would have
        produced, byte for byte.
      * temp > 0: speculative REJECTION sampling — draft d is accepted
        with probability ``p_j(d)`` (the one-hot-proposal case of
        min(1, p/q)); on rejection the emission resamples from the
        residual ``norm(p_j - onehot(d))``, and the position after the
        last accepted draft samples from ``p_j`` directly. The emitted
        distribution is exactly the target's (Leviathan et al. 2023).

    ``live[j, s]`` marks step j of slot s emitted: live_0 = remaining>0,
    live_{j+1} = live_j & accept & no-EOS & within ``remaining``. The
    dispatch-boundary ``commit_staging`` scatter redirects every
    NON-live row to the slot's private trash page — a rejected branch
    (or pad row) never dirties pool pages, so rollback is free and
    shared/COW prefix pages stay byte-stable for their other readers.
    A slot that accepts 0 drafts still emits position 0's token: one
    verify never yields fewer tokens per slot than one decode step.

    Returns ``(tokens [S, slots] int32, live [S, slots] bool, key,
    pages)``.
    """
    c = config
    n, S = tokens_mat.shape
    assert S == n_draft + 1
    kh, g = c.n_kv_heads, c.n_heads // c.n_kv_heads
    steps = jnp.arange(S, dtype=jnp.int32)
    positions = pos[:, None] + steps[None, :]              # [n, S]
    x0 = params["embed"][jnp.maximum(tokens_mat, 0)].astype(c.dtype)
    sc = stage_rows(S)
    stage_shape = (c.n_layers, n, kh, sc, c.head_dim)
    ks0 = jnp.zeros(stage_shape, pages["k"].dtype)
    vs0 = jnp.zeros(stage_shape, pages["v"].dtype)
    gather_tables = block_tables
    if not paged and live_pages is not None \
            and live_pages < block_tables.shape[1]:
        gather_tables = block_tables[:, :live_pages]
    max_ctx = gather_tables.shape[1] * page_size
    ctx_live = jnp.arange(max_ctx)[None, :] < pos[:, None]   # [n, ctx]
    causal = steps[:, None] >= steps[None, :]                # [S, S]

    def body(carry, xs):
        x, kf, vf, ks, vs = carry
        layer, l = xs
        h = rms_norm(x, layer["attn_norm"], eps=c.norm_eps)
        q, k, v = _project_qkv(h, layer)                # [n, H|KH, S, D]
        q = apply_rope(q, positions, theta=c.rope_theta)
        k = apply_rope(k, positions, theta=c.rope_theta)
        # Stage ALL S rows (accept/reject is decided after the forward);
        # the commit scatter, not the stage, is what gates the pool.
        ks = ks.at[l, :, :, :S, :].set(k.astype(ks.dtype))
        vs = vs.at[l, :, :, :S, :].set(v.astype(vs.dtype))
        qg = q.reshape(n, kh, g, S, c.head_dim)
        if paged:
            # One kernel call per chunk position: position j folds
            # staged rows [0, j] (its own causal prefix) on top of the
            # pool pages — the exact schedule decode_loop's step j uses,
            # so paged verify logits match paged decode bit for bit.
            outs = []
            for j in range(S):
                outs.append(paged_decode_attention(
                    qg[:, :, :, j], kf, vf, block_tables, pos + j,
                    page_size=page_size, live_pages=live_pages, layer=l,
                    k_stage=ks, v_stage=vs, stage_idx=j, mesh=attn_mesh))
            attn = jnp.stack(outs, axis=3)              # [n, KH, G, S, D]
        else:
            ck = _gather_ctx(kf, l, gather_tables)      # [n, KH, ctx, D]
            cv = _gather_ctx(vf, l, gather_tables)
            scale = c.head_dim ** -0.5
            s_ctx = jnp.einsum("nkgsd,nktd->nkgst", qg, ck
                               ).astype(jnp.float32)
            s_self = jnp.einsum("nkgsd,nktd->nkgst", qg, k
                                ).astype(jnp.float32)
            s_ctx = jnp.where(ctx_live[:, None, None, None],
                              s_ctx * scale, -jnp.inf)
            s_self = jnp.where(causal[None, None, None],
                               s_self * scale, -jnp.inf)
            probs = jax.nn.softmax(
                jnp.concatenate([s_ctx, s_self], axis=-1), axis=-1)
            p_ctx = probs[..., :max_ctx].astype(c.dtype)
            p_self = probs[..., max_ctx:].astype(c.dtype)
            attn = jnp.einsum("nkgst,nktd->nkgsd", p_ctx, cv) + \
                jnp.einsum("nkgst,nktd->nkgsd", p_self, v)
        attn = attn.reshape(n, c.n_heads, S, c.head_dim)
        flat = jnp.swapaxes(attn, 1, 2).reshape(n, S, -1)
        out = jnp.einsum("nsf,fe->nse", flat,
                         layer["wo"].reshape(c.n_heads * c.head_dim,
                                             c.hidden))
        return (_mlp(x + out, layer, c), kf, vf, ks, vs), None

    (x, kf, vf, ks, vs), _ = lax.scan(
        body, (x0, pages["k"], pages["v"], ks0, vs0),
        (params["layers"], jnp.arange(c.n_layers)))
    hidden = rms_norm(x, params["final_norm"], eps=c.norm_eps)  # [n, S, E]
    logits = jnp.einsum("nse,ev->nsv", hidden,
                        params["lm_head"]).astype(jnp.float32)

    # ----- acceptance + emission, all on device (one sync total) -----
    vocab = logits.shape[-1]
    # Draft considered AT step j is tokens_mat[:, j + 1]; the last step
    # has none (-1) — its emission is the bonus/fresh sample.
    d_ext = jnp.concatenate(
        [tokens_mat[:, 1:], jnp.full((n, 1), -1, jnp.int32)], axis=1)
    valid = d_ext >= 0
    d_clip = jnp.maximum(d_ext, 0)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)   # [n, S]
    p = jax.nn.softmax(
        logits / jnp.maximum(temps, 1e-6)[:, None, None], axis=-1)
    p_draft = jnp.take_along_axis(p, d_clip[..., None], axis=-1)[..., 0]
    key, ku, kr = jax.random.split(key, 3)
    u = jax.random.uniform(ku, p_draft.shape)
    accept_sampled = valid & (u < p_draft)
    # Residual distribution norm(max(p - q, 0)) for a one-hot proposal:
    # zero the draft index, renormalize (categorical normalizes).
    padj = p * (1.0 - jax.nn.one_hot(d_clip, vocab, dtype=p.dtype)
                * valid[..., None].astype(p.dtype))
    resample = jax.random.categorical(
        kr, jnp.log(padj + 1e-30)).astype(jnp.int32)
    o_sampled = jnp.where(accept_sampled, d_clip, resample)
    sampled_on = (temps > 0.0)[:, None]
    o = jnp.where(sampled_on, o_sampled, greedy).astype(jnp.int32)
    accept = jnp.where(sampled_on, accept_sampled,
                       valid & (greedy == d_clip))
    cont = accept & (o != eos_ids[:, None]) \
        & (remaining[:, None] > steps[None, :] + 1)
    live = jnp.concatenate(
        [jnp.ones((n, 1), bool),
         jnp.cumprod(cont[:, :-1].astype(jnp.int32), axis=1).astype(bool)],
        axis=1) & (remaining > 0)[:, None]                   # [n, S]

    # Dispatch-boundary commit: live rows land at their real (page,
    # offset); rejected/pad rows go to the slot's trash page — the pool
    # only ever sees ACCEPTED K/V, so a rolled-back branch is free.
    page_of = jnp.take_along_axis(
        block_tables,
        jnp.minimum(positions // page_size, block_tables.shape[1] - 1),
        axis=1)
    trash = jnp.arange(n, dtype=jnp.int32)
    widx = jnp.where(live, page_of, trash[:, None])          # [n, S]
    pages = commit_staging({"k": kf, "v": vf}, (ks, vs),
                           jnp.swapaxes(widx, 0, 1), pos, S, page_size)
    return jnp.swapaxes(o, 0, 1), jnp.swapaxes(live, 0, 1), key, pages
