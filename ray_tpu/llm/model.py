"""Prefill / decode forward passes over a slot KV cache.

Redesign of what the reference delegates to vLLM's paged attention
(``python/ray/llm/_internal/serve/deployments/llm/vllm/vllm_models.py``):
on TPU, dynamic page tables defeat XLA's static-shape compilation, so the
cache is a dense tensor ``[layers, slots, kv_heads, max_len, head_dim]``.
A sequence owns one slot for its lifetime (JetStream's insert/generate
layout); admission control in the engine replaces page allocation.

Invariant: before a decode step for a sequence at position ``pos``, the
cache holds K/V for positions ``[0, pos)``; the step writes position
``pos`` and attends over ``[0, pos]``. Prefill pads prompts to a bucket
length — padded garbage beyond ``true_len`` is progressively overwritten
by decode before it ever enters an attention window, so no masking state
is needed beyond the position counter.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from ..models.llama import LlamaConfig
from ..ops import apply_rope, rms_norm


def init_cache(config: LlamaConfig, max_slots: int, max_len: int) -> dict:
    c = config
    shape = (c.n_layers, max_slots, c.n_kv_heads, max_len, c.head_dim)
    return {"k": jnp.zeros(shape, c.dtype), "v": jnp.zeros(shape, c.dtype)}


def _project_qkv(h, layer, c: LlamaConfig):
    q = jnp.einsum("bse,ehd->bhsd", h, layer["wq"])
    k = jnp.einsum("bse,ehd->bhsd", h, layer["wk"])
    v = jnp.einsum("bse,ehd->bhsd", h, layer["wv"])
    return q, k, v


def _mlp(x, layer, c: LlamaConfig):
    h = rms_norm(x, layer["mlp_norm"], eps=c.norm_eps)
    gate = jnp.einsum("bse,em->bsm", h, layer["w_gate"])
    up = jnp.einsum("bse,em->bsm", h, layer["w_up"])
    ff = jax.nn.silu(gate.astype(jnp.float32)).astype(c.dtype) * up
    return x + jnp.einsum("bsm,me->bse", ff, layer["w_down"])


@functools.partial(jax.jit, static_argnames=("config",))
def prefill(params, tokens, config: LlamaConfig):
    """Full causal forward on one padded prompt, collecting per-layer K/V.

    tokens: [1, S] int32 (S = a static bucket length).
    Returns (k_layers [L, KH, S, D], v_layers, hidden [1, S, E]).
    """
    c = config
    _, s = tokens.shape
    positions = jnp.arange(s, dtype=jnp.int32)
    x = params["embed"][tokens].astype(c.dtype)

    def body(carry, layer):
        h = rms_norm(carry, layer["attn_norm"], eps=c.norm_eps)
        q, k, v = _project_qkv(h, layer, c)
        q = apply_rope(q, positions, theta=c.rope_theta)
        k = apply_rope(k, positions, theta=c.rope_theta)
        # [1, H, S, D] x [1, KH, S, D] causal GQA in f32 scores.
        kh, g = c.n_kv_heads, c.n_heads // c.n_kv_heads
        qg = q.reshape(1, kh, g, s, c.head_dim)
        scores = jnp.einsum("bkgsd,bktd->bkgst", qg, k).astype(jnp.float32)
        scores *= c.head_dim ** -0.5
        causal = positions[:, None] >= positions[None, :]
        scores = jnp.where(causal[None, None, None], scores, -jnp.inf)
        probs = jax.nn.softmax(scores, axis=-1).astype(c.dtype)
        attn = jnp.einsum("bkgst,bktd->bkgsd", probs, v).reshape(1, c.n_heads, s, c.head_dim)
        out = jnp.einsum("bhsd,hde->bse", attn, layer["wo"])
        x2 = _mlp(carry + out, layer, c)
        return x2, (k[0], v[0])

    x, (ks, vs) = lax.scan(body, x, params["layers"])
    hidden = rms_norm(x, params["final_norm"], eps=c.norm_eps)
    return ks, vs, hidden


@functools.partial(jax.jit, static_argnames=("config", "max_len"),
                   donate_argnames=("cache",))
def insert_kv(cache: dict, k_layers, v_layers, slot, config: LlamaConfig, max_len: int) -> dict:
    """Copy a prefilled prompt's K/V into the cache at ``slot``.
    k_layers/v_layers: [L, KH, S, D] with S <= max_len (padded to bucket)."""
    L, KH, S, D = k_layers.shape
    pad = max_len - S
    if pad:
        k_layers = jnp.pad(k_layers, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v_layers = jnp.pad(v_layers, ((0, 0), (0, 0), (0, pad), (0, 0)))
    k = lax.dynamic_update_slice(cache["k"], k_layers[:, None], (0, slot, 0, 0, 0))
    v = lax.dynamic_update_slice(cache["v"], v_layers[:, None], (0, slot, 0, 0, 0))
    return {"k": k, "v": v}


@functools.partial(jax.jit, static_argnames=("config",), donate_argnames=("cache",))
def decode_step(params, cache: dict, tokens, pos, config: LlamaConfig):
    """One batched decode step over all slots.

    tokens: [slots] int32 — the token at position ``pos[i]`` of each
    sequence (garbage for inactive slots; the engine ignores their output).
    pos:    [slots] int32 — write/attend position per slot.
    Returns (logits [slots, vocab] f32, new cache).
    """
    c = config
    n = tokens.shape[0]
    max_len = cache["k"].shape[3]
    x = params["embed"][tokens][:, None].astype(c.dtype)  # [slots, 1, E]
    kh, g = c.n_kv_heads, c.n_heads // c.n_kv_heads

    def write(cache_l, new, p):
        # cache_l [KH, max_len, D], new [KH, D] -> write at position p
        return lax.dynamic_update_slice(cache_l, new[:, None], (0, p, 0))

    def body(carry, xs):
        x = carry
        layer, ck, cv = xs  # ck/cv: [slots, KH, max_len, D]
        h = rms_norm(x, layer["attn_norm"], eps=c.norm_eps)
        q, k, v = _project_qkv(h, layer, c)  # [slots, H|KH, 1, D]
        q = apply_rope(q, pos[:, None], theta=c.rope_theta)
        k = apply_rope(k, pos[:, None], theta=c.rope_theta)
        ck = jax.vmap(write)(ck, k[:, :, 0], pos)
        cv = jax.vmap(write)(cv, v[:, :, 0], pos)
        qg = q[:, :, 0].reshape(n, kh, g, c.head_dim)
        scores = jnp.einsum("nkgd,nktd->nkgt", qg, ck).astype(jnp.float32)
        scores *= c.head_dim ** -0.5
        live = jnp.arange(max_len)[None] <= pos[:, None]  # [slots, max_len]
        scores = jnp.where(live[:, None, None], scores, -jnp.inf)
        probs = jax.nn.softmax(scores, axis=-1).astype(c.dtype)
        attn = jnp.einsum("nkgt,nktd->nkgd", probs, cv).reshape(n, 1, c.n_heads * c.head_dim)
        out = jnp.einsum("bsf,fe->bse", attn,
                         layer["wo"].reshape(c.n_heads * c.head_dim, c.hidden))
        x2 = _mlp(x + out, layer, c)
        return x2, (ck, cv)

    x, (new_k, new_v) = lax.scan(body, x, (params["layers"], cache["k"], cache["v"]))
    hidden = rms_norm(x, params["final_norm"], eps=c.norm_eps)  # [slots, 1, E]
    logits = jnp.einsum("bse,ev->bsv", hidden, params["lm_head"])[:, 0]
    return logits.astype(jnp.float32), {"k": new_k, "v": new_v}
