"""TPU accelerator detection and slice-aware resource shaping.

Equivalent of the reference's ``TPUAcceleratorManager``
(``python/ray/_private/accelerators/tpu.py:70``, 393 LoC): detects TPU
hardware (GCE/GKE metadata or a live JAX backend), exposes per-host chip
counts as a ``TPU`` resource, sets chip-visibility env vars for workers, and
auto-creates the ``TPU-{type}-head`` resource on host 0 of a pod slice so a
single slice-head bundle can anchor STRICT_PACK placement groups
(reference ``tpu.py:31-44,170-192``).
"""

from __future__ import annotations

import functools
import os

# GKE/GCE environment variables (reference tpu.py:31-44).
_ENV_ACCEL_TYPE = "TPU_ACCELERATOR_TYPE"  # e.g. "v5litepod-16"
_ENV_WORKER_ID = "TPU_WORKER_ID"
_ENV_CHIPS_PER_HOST = "TPU_CHIPS_PER_HOST_BOUNDS"
_ENV_TPU_NAME = "TPU_NAME"
ENV_VISIBLE_CHIPS = "TPU_VISIBLE_CHIPS"


@functools.lru_cache(maxsize=1)
def detect_num_tpu_chips() -> int:
    """Number of TPU chips attached to this host."""
    override = os.environ.get("RAY_TPU_FAKE_CHIPS")
    if override:
        return int(override)
    bounds = os.environ.get(_ENV_CHIPS_PER_HOST)
    if bounds:
        # e.g. "2,2,1" → 4 chips (reference tpu.py:170-192)
        dims = [int(x) for x in bounds.split(",")]
        n = 1
        for d in dims:
            n *= d
        return n
    # Environment-based detection works even when THIS process runs with
    # JAX_PLATFORMS=cpu (the driver advertises the chip; a worker with a
    # cleared override claims it) — asking JAX here would initialize the
    # TPU backend in the driver, claiming the chip it must stay off.
    if os.environ.get("PALLAS_AXON_TPU_GEN"):
        return 1  # axon tunnel exposes one chip
    acc = os.environ.get(_ENV_ACCEL_TYPE)
    if acc:
        override = os.environ.get("RAY_TPU_CHIPS_PER_HOST")
        if override:
            return int(override)
        return _chips_per_host_for_type(acc)
    # Fall back to asking JAX (only reached when no TPU env markers exist,
    # so this cannot initialize a TPU backend by surprise).
    try:
        import jax

        return sum(1 for d in jax.devices() if "tpu" in d.platform.lower() or "axon" in str(getattr(d, "client", "")).lower() or d.platform == "axon")
    except Exception:
        return 0


def _chips_per_host_for_type(acc: str) -> int:
    """Chips THIS host contributes to the slice, derived per generation
    (reference ``_private/accelerators/tpu.py:170-192``) — the suffix
    counts CORES on v2/v3/v4/v5p (2 cores/chip, 4 chips/host) but CHIPS
    on v5e/v6e (single host up to 8, pods 4/host). The old suffix-only
    guess mis-sized e.g. v4-8 (4 chips, not 8)."""
    gen, _, suffix = acc.rpartition("-")
    gen = gen.lower()
    try:
        n = int(suffix)
    except ValueError:
        return 4
    if gen in ("v2", "v3", "v4", "v5p"):
        chips_total = max(1, n // 2)  # suffix counts cores
        return min(4, chips_total)    # 4 chips per host
    # v5litepod / v5e / v6e: suffix counts chips; <=8 fits one host
    return n if n <= 8 else 4


@functools.lru_cache(maxsize=1)
def accelerator_type() -> str:
    """Slice type string like 'v5litepod-16', '' when not on TPU."""
    return os.environ.get(_ENV_ACCEL_TYPE, "")


def slice_name() -> str:
    return os.environ.get(_ENV_TPU_NAME, "")


def worker_index() -> int:
    return int(os.environ.get(_ENV_WORKER_ID, "0"))


def detect_tpu_resources() -> dict[str, float]:
    """Resources this host contributes.

    ``TPU``: chips on this host. ``TPU-{type}-head``: 1 on worker 0 of a
    slice so placement groups can target 'one bundle per slice'
    (reference tpu.py:70-192 get_current_node_tpu_pod_type etc.).
    """
    chips = detect_num_tpu_chips()
    if chips <= 0:
        return {}
    out: dict[str, float] = {"TPU": float(chips)}
    acc = accelerator_type()
    if acc and worker_index() == 0:
        out[f"TPU-{acc}-head"] = 1.0
    if slice_name():
        out[f"TPU-{slice_name()}"] = float(chips)
    return out


def num_hosts_for_type(acc_type: str) -> int:
    """Hosts in a slice of the given type, e.g. v5litepod-16 → 4 hosts.

    v5e: 4 chips/host (v5litepod-8 → 2 hosts); v5p/v4: 4 chips/host;
    suffix is the chip count for v4/v5p is cores — keep the simple
    chips/4 rule the reference uses for pod slices.
    """
    try:
        n_chips = int(acc_type.rsplit("-", 1)[1])
    except (IndexError, ValueError):
        return 1
    return max(1, n_chips // 4)


def set_visible_chips(chip_ids: list[int]) -> dict[str, str]:
    """Env vars pinning a worker to a subset of host chips
    (reference tpu.py sets TPU_VISIBLE_CHIPS / TPU_CHIPS_PER_HOST_BOUNDS)."""
    return {
        ENV_VISIBLE_CHIPS: ",".join(str(c) for c in chip_ids),
        "TPU_CHIPS_PER_PROCESS_BOUNDS": "1,1,1",
        "TPU_PROCESS_BOUNDS": "1,1,1",
    }
