"""Headline benchmark: Llama train throughput THROUGH the framework.

Runs ``JaxTrainer.fit`` — controller → placement group → train-worker
actor (which claims the TPU via runtime_env) → Data streaming split →
report/checkpoint — and prints ONE JSON line
``{"metric", "value", "unit", "vs_baseline"}`` plus MFU and the raw-loop
number so framework overhead is visible.

North star (BASELINE.json) is Ray Train tokens/sec/chip on Llama-3; the
reference has no TPU number, so vs_baseline compares against
BENCH_BASELINE.json when present (else 1.0).
"""

from __future__ import annotations

import json
import os
import sys
import time

# The driver stays OFF the TPU: the train worker claims the chip.
os.environ.setdefault("JAX_PLATFORMS", "cpu")

PRESET = os.environ.get("RAY_TPU_BENCH_PRESET", "llama3-1b")
BATCH = int(os.environ.get("RAY_TPU_BENCH_BATCH", "8"))
SEQ = int(os.environ.get("RAY_TPU_BENCH_SEQ", "2048"))
TIMED_STEPS = int(os.environ.get("RAY_TPU_BENCH_STEPS", "10"))
WARMUP_STEPS = 2
ALLOW_CPU = os.environ.get("RAY_TPU_BENCH_ALLOW_CPU") == "1"  # plumbing smoke test


def train_fn(config: dict) -> None:
    """Runs inside the TPU-owning train worker actor."""
    import functools

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from ray_tpu import train
    from ray_tpu.models import PRESETS, init_params, loss_fn, param_axes
    from ray_tpu.models.llama import train_flops_per_token
    from ray_tpu.parallel import MeshConfig, create_mesh
    from ray_tpu.parallel.sharding import shard_params
    import dataclasses

    if config.get("allow_cpu"):
        # smoke mode: force-pin CPU (the container sitecustomize registers
        # the TPU plugin and wins over the env var)
        jax.config.update("jax_platforms", "cpu")
    else:
        platform = jax.devices()[0].platform
        # the axon tunnel reports platform "axon" for the same chip
        assert platform in ("tpu", "axon"), f"worker got {jax.devices()}"
    n_dev = len(jax.devices())
    mesh = create_mesh(MeshConfig(dp=n_dev))
    cfg = dataclasses.replace(PRESETS[config["preset"]], remat_policy="attn")
    batch_per_chip, seq = config["batch"], config["seq"]

    params = init_params(cfg, jax.random.PRNGKey(0))
    params = shard_params(params, param_axes(cfg), mesh)
    opt = optax.adafactor(1e-3)
    opt_state = jax.jit(opt.init)(params)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def train_step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(
            lambda p: loss_fn(p, batch, cfg, mesh=mesh, chunk_tokens=2048)
        )(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    shard = train.get_dataset_shard("train")
    batches = shard.iter_batches(batch_size=batch_per_chip * n_dev, drop_last=True)

    def next_batch():
        host = next(batches)
        return {"tokens": jax.device_put(np.asarray(host["tokens"], np.int32))}

    # warmup / compile. NOTE: under the axon tunnel block_until_ready is a
    # no-op; device_get is the only reliable completion fence.
    for _ in range(WARMUP_STEPS):
        params, opt_state, loss = train_step(params, opt_state, next_batch())
    float(jax.device_get(loss))

    t0 = time.perf_counter()
    for _ in range(config["steps"]):
        params, opt_state, loss = train_step(params, opt_state, next_batch())
    final_loss = float(jax.device_get(loss))
    dt = time.perf_counter() - t0

    tokens_per_sec_per_chip = batch_per_chip * seq * config["steps"] / dt
    mfu = tokens_per_sec_per_chip * train_flops_per_token(cfg, seq) / 197e12

    # checkpoint through the framework path (outside the timed region)
    import tempfile

    from ray_tpu.train import Checkpoint, save_pytree

    with tempfile.TemporaryDirectory() as d:
        save_pytree({"step": jnp.asarray(config["steps"])}, d)
        train.report(
            {"tokens_per_sec_per_chip": tokens_per_sec_per_chip, "mfu": mfu,
             "loss": final_loss},
            checkpoint=Checkpoint.from_directory(d),
        )


def run_framework() -> dict:
    import numpy as np

    import ray_tpu
    from ray_tpu import data
    from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig

    ray_tpu.init(num_cpus=4)
    total_steps = WARMUP_STEPS + TIMED_STEPS
    # synthetic token stream through the real Data path
    # sized for up to 8 devices in the worker (the driver can't see the
    # worker's device count; int32 tokens are cheap)
    rows = (total_steps + 2) * BATCH * 8
    tokens = np.random.randint(0, 128_256, size=(rows, SEQ), dtype=np.int32)
    ds = data.from_numpy(tokens, column="tokens")

    trainer = JaxTrainer(
        train_fn,
        train_loop_config={"preset": PRESET, "batch": BATCH, "seq": SEQ,
                           "steps": TIMED_STEPS, "allow_cpu": ALLOW_CPU},
        scaling_config=ScalingConfig(
            num_workers=1,
            resources_per_worker={"CPU": 1} if ALLOW_CPU else {"CPU": 1, "TPU": 1},
            # the worker (not the driver) owns the chip
            worker_runtime_env=None if ALLOW_CPU else {"env_vars": {"JAX_PLATFORMS": None}},
        ),
        run_config=RunConfig(name=f"bench_{int(time.time())}", storage_path="/tmp/ray_tpu/bench"),
        datasets={"train": ds},
    )
    result = trainer.fit()
    if result.error is not None:
        raise result.error
    out = dict(result.metrics)
    out.update(collect_memory_peaks())
    ray_tpu.shutdown()
    return out


def collect_memory_peaks() -> dict:
    """Peak HBM and object-store bytes from the cluster's memory gauges
    (must run while still connected): lets the perf trajectory correlate
    throughput regressions with memory pressure."""
    try:
        from ray_tpu.util.metrics import get_metrics

        rows = get_metrics()

        def peak(name: str) -> int:
            return int(max((m["value"] for m in rows if m["name"] == name),
                           default=0))

        return {
            "peak_hbm_used_bytes": peak("ray_tpu_hbm_peak_bytes"),
            "peak_object_store_bytes": peak("ray_tpu_object_store_used_peak_bytes"),
        }
    except Exception as e:
        print(f"memory peak collection failed: {e}", file=sys.stderr)
        return {}


def _run_chip_subprocess(code: str, what: str, timeout: float = 900) -> dict:
    """Run a measurement snippet in a fresh process that owns the chip
    (the driver stays on CPU); returns the last JSON OBJECT line of its
    stdout. One shared scaffold so the env handling and the axon-fence
    parse convention can't drift between benchmarks."""
    import subprocess

    env = dict(os.environ)
    if not ALLOW_CPU:
        env.pop("JAX_PLATFORMS", None)  # the subprocess owns the chip
    out = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True, env=env,
        timeout=timeout,
    )
    for line in reversed(out.stdout.strip().splitlines()):
        try:
            parsed = json.loads(line)
        except Exception:
            continue
        if isinstance(parsed, dict):
            return parsed
    raise RuntimeError(f"{what} benchmark failed: {out.stderr[-2000:]}")


def run_raw(preset: str | None = None, batch: int | None = None,
            seq: int | None = None) -> float:
    """The same train step without the framework (overhead comparison;
    also reused for the 8B-shape and long-context perf points)."""
    preset = preset or PRESET
    batch = batch or BATCH
    seq = seq or SEQ
    code = r"""
import dataclasses, functools, json, os, time
import jax, jax.numpy as jnp, optax
if os.environ.get("RAY_TPU_BENCH_ALLOW_CPU") == "1":
    jax.config.update("jax_platforms", "cpu")
from ray_tpu.models import PRESETS, init_params, loss_fn, param_axes
from ray_tpu.parallel import MeshConfig, create_mesh
from ray_tpu.parallel.sharding import shard_params
n_dev = len(jax.devices())
mesh = create_mesh(MeshConfig(dp=n_dev))
cfg = dataclasses.replace(PRESETS["%s"], remat_policy="attn")
params = shard_params(init_params(cfg, jax.random.PRNGKey(0)), param_axes(cfg), mesh)
opt = optax.adafactor(1e-3)
opt_state = jax.jit(opt.init)(params)
tokens = jax.random.randint(jax.random.PRNGKey(1), (%d * n_dev, %d), 0, cfg.vocab_size)
batch = {"tokens": tokens}
@functools.partial(jax.jit, donate_argnums=(0, 1))
def step(params, opt_state, batch):
    loss, grads = jax.value_and_grad(lambda p: loss_fn(p, batch, cfg, mesh=mesh, chunk_tokens=2048))(params)
    updates, opt_state = opt.update(grads, opt_state, params)
    return optax.apply_updates(params, updates), opt_state, loss
for _ in range(%d):
    params, opt_state, loss = step(params, opt_state, batch)
float(jax.device_get(loss))
t0 = time.perf_counter()
for _ in range(%d):
    params, opt_state, loss = step(params, opt_state, batch)
float(jax.device_get(loss))
print(json.dumps({"raw": %d * %d * %d / (time.perf_counter() - t0)}))
""" % (preset, batch, seq, WARMUP_STEPS, TIMED_STEPS, batch, seq, TIMED_STEPS)
    return _run_chip_subprocess(code, "raw")["raw"]


def run_longctx() -> dict:
    """Long-context points on the real chip (VERDICT r3 item 8):
    the Pallas flash kernel at llama3-1b attention shapes (Hq=32, Hkv=8,
    head_dim=64, GQA) swept over seq 512 → 32768, fwd+bwd TFLOP/s each,
    plus a full 1B train step at seq 8192 (remat, batch 1) for the
    end-to-end long-context tokens/s."""
    code = r"""
import json, os, time
import jax, jax.numpy as jnp
out = {}
B, Hq, Hkv, D = 1, 32, 8, 64
from ray_tpu.ops import flash_attention
kq, kk, kv, kg = jax.random.split(jax.random.PRNGKey(0), 4)
for S in (512, 4096, 32768):
    q = jax.random.normal(kq, (B, Hq, S, D), jnp.bfloat16)
    k = jax.random.normal(kk, (B, Hkv, S, D), jnp.bfloat16)
    v = jax.random.normal(kv, (B, Hkv, S, D), jnp.bfloat16)
    g = jax.random.normal(kg, (B, Hq, S, D), jnp.bfloat16)

    @jax.jit
    def fwdbwd(q, k, v, g):
        def f(q, k, v):
            return (flash_attention(q, k, v, causal=True).astype(jnp.float32) * g).sum()
        l, grads = jax.value_and_grad(f, argnums=(0, 1, 2))(q, k, v)
        return l, grads

    l, grads = fwdbwd(q, k, v, g)   # compile
    float(jax.device_get(l))
    # Timed window sized >= ~0.5 s and run TWICE, best kept: at s4096 the
    # old 20-iter window was ~190 ms with one ~65 ms axon device_get
    # fence inside it, so node-to-node dispatch/fence variance moved the
    # recorded TFLOP/s by >10% with zero kernel change (the r04->r05
    # 26.16 -> 22.99 "regression" — PERF.md round 6).
    iters = 60 if S <= 4096 else 8
    best = None
    for _ in range(2):
        t0 = time.perf_counter()
        for _ in range(iters):
            l, grads = fwdbwd(q, k, v, g)
        float(jax.device_get(l))
        dt = (time.perf_counter() - t0) / iters
        best = dt if best is None else min(best, dt)
    # causal fwd = 2*B*Hq*S^2*D FLOP (QK^T + PV, halved by causality);
    # bwd recomputes fwd scores and adds dQ/dK/dV ~ 2.5x fwd
    flops = 3.5 * 2 * B * Hq * S * S * D
    out[f"flash_fwdbwd_tflops_s{S}"] = round(flops / best / 1e12, 2)
print(json.dumps(out))
"""
    metrics = _run_chip_subprocess(code, "longctx flash")
    # end-to-end long-context train point: 1B, seq 8192, batch 1, remat
    try:
        tok_s = run_raw(preset="llama3-1b", batch=1, seq=8192)
        from ray_tpu.models.llama import PRESETS as _P, train_flops_per_token

        metrics["train_tok_s_1b_seq8k"] = round(tok_s, 1)
        metrics["mfu_1b_seq8k"] = round(
            tok_s * train_flops_per_token(_P["llama3-1b"], 8192) / 197e12, 4)
    except Exception as e:
        metrics["longctx_train_error"] = f"{type(e).__name__}: {e}"
    return metrics


def _serve_failure_details() -> str:
    """Name the replica startup exception (propagated since the
    diagnostics PR) so a failed serve bench records WHAT died, not just
    that the app never became healthy — r05's serve_error carried no
    cause and cost a round of guessing."""
    parts = []
    try:
        from ray_tpu import serve

        for app, deps in (serve.status() or {}).items():
            for name, st in deps.items():
                if st.get("last_start_failure"):
                    parts.append(f"{app}/{name} last_start_failure: "
                                 f"{st['last_start_failure'].splitlines()[0]}")
    except Exception as e:
        parts.append(f"serve.status unavailable: {e}")
    try:
        from ray_tpu.util.state import list_errors

        for err in list_errors(error_type="replica_start_failure")[-3:]:
            parts.append(f"error event: {err.get('message', '')[:300]}")
    except Exception:
        pass
    return " | ".join(parts) or "no startup failure recorded"


def run_paged_bench() -> dict:
    """Paged-v2 vs dense decode on the chip (ROADMAP item 3 acceptance):
    aggregate fused-decode throughput at llama3-1b for

      * a UNIFORM batch — 8 slots, 2k live context each (dense's best
        case: batch-max == per-slot context), and
      * the SKEWED batch — 1 slot at 8k + 7 slots at 256 (the shape the
        per-SLOT HBM proportionality exists for: dense gathers the 8k
        batch-max width for all 8 slots).

    Context is synthesized directly into block tables/pos (decode cost
    does not depend on KV values), so the measurement is pure decode.
    Also reports the per-step analytic KV-read traffic of each path —
    the PERF.md "HBM per step" row."""
    code = r"""
import json, time
import numpy as np
import jax
from ray_tpu.llm.executor import LocalEngineExecutor
from ray_tpu.models.llama import PRESETS

cfg = PRESETS["llama3-1b"]
page, slots, K = 16, 8, 32
out = {}
for name, ctxs in (("uniform", [2048] * 8),
                   ("skewed", [8192] + [256] * 7)):
    max_pages = max(ctxs) // page
    num_pages = slots + sum(-(-c // page) for c in ctxs) + slots  # + headroom
    for impl in ("dense", "paged"):
        ex = LocalEngineExecutor(
            cfg, max_slots=slots, num_pages=num_pages, page_size=page,
            attention_impl=impl, seed=0)
        bt = np.tile(np.arange(slots, dtype=np.int32)[:, None],
                     (1, max_pages))
        nxt = slots
        for s, c in enumerate(ctxs):
            n = -(-c // page)
            bt[s, :n] = np.arange(nxt, nxt + n, dtype=np.int32)
            nxt += n
        pos = np.asarray(ctxs, np.int32) - K - 1   # headroom for K steps
        tokens = np.ones(slots, np.int32)
        temps = np.zeros(slots, np.float32)
        eos = np.full(slots, -1, np.int32)
        remaining = np.full(slots, 10_000, np.int32)
        ex.decode(bt, tokens, pos, temps, eos, remaining, K)  # compile
        iters = 6
        t0 = time.perf_counter()
        for i in range(iters):
            ex.decode(bt, tokens, pos, temps, eos, remaining, K)
        dt = (time.perf_counter() - t0) / iters
        out[f"decode_tok_s_{name}_{impl}"] = round(slots * K / dt, 1)
        del ex
        import gc; gc.collect()  # free params+pool before the next build
    # analytic KV bytes READ per decode step (bf16, both k and v):
    # dense gathers the bucketed batch-max width for every slot; paged
    # reads each slot's live pages only.
    row = 2 * cfg.n_layers * cfg.n_kv_heads * cfg.head_dim * 2  # k+v bytes/token
    live = sum(ctxs)
    batch_max = max(ctxs) * slots
    out[f"kv_read_mb_step_{name}_paged"] = round(row * live / 1e6, 1)
    out[f"kv_read_mb_step_{name}_dense"] = round(row * batch_max / 1e6, 1)
print(json.dumps(out))
"""
    return _run_chip_subprocess(code, "paged decode", timeout=1200)


def run_core_bench() -> dict:
    """Core task-path throughput (ROADMAP item 3): no-op task, actor-call,
    and object put/get round-trip rates through the REAL
    submit→lease→push→return path, plus the lease-stage p50s the run
    produced. Implementation lives in ``ray_tpu/_core_bench.py`` (also
    runnable standalone: ``python -m ray_tpu.cli bench core``)."""
    from ray_tpu._core_bench import run_core_bench as _run

    return _run()


def run_dag_bench() -> dict:
    """Compiled-loop dispatch suite (ROADMAP item 4): per-tick dispatch
    overhead dynamic vs compiled (`dag_tick_dispatch_overhead*_us`,
    `dag_loop_ticks_per_s`) and the pp=2 engine decode rate through both
    paths (`pp_decode_tok_s_{dynamic,compiled}`; skip markers on hosts
    that can't run the pp shard_map). Implementation in
    ``ray_tpu/_dag_bench.py``; standalone: ``python -m ray_tpu.cli bench
    dag``."""
    from ray_tpu._dag_bench import run_dag_bench as _run

    return _run()


def run_recovery_bench() -> dict:
    """Preemption recovery SLOs (ROADMAP item 6): preempt-mid-train and
    preempt-mid-serve through the real notice→drain→kill path, recording
    `recovery_train_resume_s`, `recovery_serve_reroute_s`, and
    `recovery_ckpt_lag_steps` (chaos-clock measured; `*_skipped` markers
    on scenarios that cannot run). Implementation in
    ``ray_tpu/_recovery_bench.py``; standalone: ``python -m ray_tpu.cli
    bench recovery``."""
    from ray_tpu._recovery_bench import run_recovery_bench as _run

    return _run()


def run_overload_bench() -> dict:
    """Overload-protection cells (ISSUE 12): goodput under a 2×-capacity
    thundering herd with protection ON (`serve_goodput_frac` — must
    strictly beat the protection-OFF `serve_goodput_frac_unprotected`
    baseline cell), the p95 time-to-503 of shed requests
    (`serve_shed_fast_fail_p95_ms`), admitted-request TTFT p95, and
    greedy byte parity of admitted reference prompts. Implementation in
    ``ray_tpu/_overload_bench.py``; standalone: ``python -m ray_tpu.cli
    bench overload``."""
    from ray_tpu._overload_bench import run_overload_bench as _run

    return _run()


def run_migration_bench() -> dict:
    """KV-migration cells (ROADMAP item 2): migrated vs cold TTFT at the
    2k-prompt cell (`serve_ttft_migrated_ms` must be ≤ 0.7× the cold
    cell) plus the raw page-transfer throughput `kv_migration_mb_s`,
    with greedy byte parity asserted between the migrated and cold
    serves. Implementation in ``ray_tpu/_migration_bench.py``;
    standalone: ``python -m ray_tpu.cli bench migration``."""
    from ray_tpu._migration_bench import run_migration_bench as _run

    return _run()


def run_serve_bench() -> dict:
    """Serve p50 TTFT north star (BASELINE.json): concurrent streaming
    completions through the REAL stack — HTTP proxy → pow-2 router →
    replica → paged continuous-batching engine on the chip — measuring
    time-to-first-SSE-token and aggregate decode throughput."""
    import statistics
    import threading
    import urllib.request

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.llm import build_llm_app

    preset = os.environ.get("RAY_TPU_SERVE_PRESET", "llama3-1b" if not ALLOW_CPU else "debug-128")
    n_clients = int(os.environ.get("RAY_TPU_SERVE_CLIENTS", "8"))
    decode_k = int(os.environ.get("RAY_TPU_SERVE_DECODE_K", "32"))
    reqs_per_client = int(os.environ.get("RAY_TPU_SERVE_REQS", "6"))
    max_tokens = int(os.environ.get("RAY_TPU_SERVE_MAX_TOKENS", "64"))
    # max_len must cover the matrix's 2k-token prompt cell (+ generation
    # headroom); the decode cost stays proportional to LIVE context (the
    # live_pages bucketing), so the short-prompt phases don't pay for it.
    max_len = int(os.environ.get("RAY_TPU_SERVE_MAX_LEN", "2560"))

    ray_tpu.init(num_cpus=8, ignore_reinit_error=True)
    app = build_llm_app(
        preset,
        max_slots=8,
        max_len=max_len,
        page_size=64,
        prefill_chunk_size=256,
        # 32 fused decode steps per dispatch: the axon dispatch channel
        # costs ~200-300 ms per round trip, so K=16->32 lifts aggregate
        # decode ~31% (582->764 tok/s measured) for ~100 ms added join
        # delay on in-flight batches — the right trade at this overhead.
        decode_steps_per_dispatch=decode_k,
        max_ongoing_requests=32,
        ray_actor_options=None if ALLOW_CPU else {
            "resources": {"TPU": 1},
            "runtime_env": {"env_vars": {"JAX_PLATFORMS": None}},
        },
    )
    # Health window sized for TWO replica attempts. Both r04 and r05
    # showed the FIRST replica after the raw-bench chip handoff burning
    # ~65 s before dying (the grant fence waits for the libtpu lock, but
    # the previous holder's teardown can outlast it) and the replacement
    # needing another ~60 s of 1B param init + compile; r04 squeaked
    # inside 120 s on a fast node, r05's node missed it and the round
    # recorded NO serve TTFT at all. 360 s covers the failure+replace
    # cycle with margin; a genuine crash-loop still fails fast below via
    # the surfaced last_start_failure.
    try:
        serve.run(app, name="llm-bench", timeout_s=360.0)
    except Exception as e:
        print(f"serve.run: {e}\nserve startup diagnostics: "
              f"{_serve_failure_details()}", file=sys.stderr)
        # One retry: by now the controller's replace loop has usually
        # converged (deploying the same app is idempotent).
        serve.run(app, name="llm-bench", timeout_s=240.0)
    addr = serve.http_address()

    def one_request(prompt: str, timeout: float = 600.0,
                    session: str = ""):
        """Returns (ttft_s, n_tokens, wall_s, itl_gaps_s): itl_gaps are
        the client-observed delays between consecutive SSE token events —
        the inter-token latency the mixed-dispatch scheduler bounds.
        ``session`` sets the x-raytpu-session header: the router pins the
        request to its prefix group's affine replica."""
        body = json.dumps({"prompt": prompt, "max_tokens": max_tokens,
                           "stream": True}).encode()
        headers = {"Content-Type": "application/json"}
        if session:
            headers["x-raytpu-session"] = session
        req = urllib.request.Request(
            addr + "/v1/completions", data=body, headers=headers)
        t0 = time.perf_counter()
        ttft = None
        last_tok = None
        gaps: list[float] = []
        n_tokens = 0
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            for line in resp:
                line = line.decode().strip()
                if line.startswith("data: ") and line != "data: [DONE]":
                    now = time.perf_counter()
                    if ttft is None:
                        ttft = now - t0
                    else:
                        gaps.append(now - last_tok)
                    last_tok = now
                    n_tokens += 1
        return ttft, n_tokens, time.perf_counter() - t0, gaps

    # Warmup: compile prefill buckets + decode program.
    one_request("w" * 90)
    one_request("x" * 200)

    # Phase 1 — unloaded service time: sequential requests, no queueing.
    # The spread between this TTFT and the loaded p50 below is queueing +
    # batching delay, not model time (VERDICT r3 weak #2 decomposition).
    ttft_unloaded = []
    for j in range(4):
        try:
            t, _, _, _ = one_request(f"unloaded {j}: " + "abcd" * 12)
        except Exception as e:  # best-effort: the loaded phase still runs
            print(f"unloaded-ttft request failed: {e}", file=sys.stderr)
            continue
        if t is not None:
            ttft_unloaded.append(t)

    ttfts: list[float] = []
    token_counts: list[int] = []
    errors: list[str] = []
    lock = threading.Lock()

    def client(cid: int) -> None:
        for j in range(reqs_per_client):
            prompt = f"client {cid} request {j}: " + "abcdefgh" * (8 + (cid + j) % 12)
            try:
                ttft, n_tok, _, _ = one_request(prompt)
            except Exception as e:
                with lock:
                    errors.append(f"{type(e).__name__}: {e}")
                return
            with lock:
                if ttft is not None:
                    ttfts.append(ttft)
                token_counts.append(n_tok)

    threads = [threading.Thread(target=client, args=(i,)) for i in range(n_clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0

    # Server-side TTFT from the serve_ttft_ms histogram (arrival → first
    # sampled token inside the engine): the queueing/SSE-transport share
    # of the client TTFT is the spread between the two numbers. The
    # replica's metrics flusher pushes every ~5s — poll until the
    # histogram covers the load phase.
    engine_ttft_p50 = None
    try:
        from ray_tpu.util.metrics import get_metrics, histogram_quantile

        deadline = time.perf_counter() + 15.0
        want = len(ttfts) + len(ttft_unloaded)
        while time.perf_counter() < deadline:
            rows = [m for m in get_metrics()
                    if m["name"] == "serve_ttft_ms" and m.get("count")]
            if rows and sum(m["count"] for m in rows) >= want:
                break
            time.sleep(1.0)
        if rows:
            best = max(rows, key=lambda m: m["count"])
            q = histogram_quantile(best, 0.5)
            engine_ttft_p50 = round(q, 1) if q is not None else None
    except Exception as e:
        print(f"engine ttft histogram unavailable: {e}", file=sys.stderr)

    # ---- serve bench MATRIX (ROADMAP item 2 acceptance): concurrency
    # {8,32} × prompt {short,2k}, each cell recording client p50/p95 TTFT
    # and the p95 inter-token latency — the number the token-budget mixed
    # scheduler exists to bound under the 32-way 2k-prompt cell. The 2k
    # prompts share a system-prompt-style prefix so the cell also
    # exercises the prefix cache (serve_prefix_cache_hit_rate below).
    matrix: dict = {}
    matrix_reqs = int(os.environ.get("RAY_TPU_SERVE_MATRIX_REQS", "3"))
    cells_env = os.environ.get("RAY_TPU_SERVE_MATRIX_CELLS", "")
    wanted_cells = {c.strip() for c in cells_env.split(",") if c.strip()}
    shared_2k_prefix = "You are a helpful assistant. " * 55  # ~1.6k tokens
    if os.environ.get("RAY_TPU_BENCH_SKIP_SERVE_MATRIX") != "1":
        for conc in (8, 32):
            for kind in ("short", "2k"):
                cell = f"c{conc}_{kind}"
                if wanted_cells and cell not in wanted_cells:
                    # Intentionally skipped: record the marker so
                    # bench_check never treats the cell's metrics as
                    # silently vanished.
                    matrix[f"serve_{cell}_skipped"] = True
                    continue
                cell_ttfts: list[float] = []
                cell_gaps: list[float] = []
                cell_errors: list[str] = []

                def cell_client(cid: int) -> None:
                    for j in range(matrix_reqs):
                        if kind == "short":
                            prompt = f"cell {cell} client {cid} req {j}: " \
                                + "abcdefgh" * (6 + (cid + j) % 8)
                        else:
                            prompt = shared_2k_prefix + \
                                f"cell {cell} client {cid} req {j}: " \
                                + "wxyz" * (80 + (cid + j) % 16)
                        try:
                            t, _, _, gaps = one_request(prompt)
                        except Exception as e:
                            with lock:
                                cell_errors.append(f"{type(e).__name__}: {e}")
                            return
                        with lock:
                            if t is not None:
                                cell_ttfts.append(t)
                            cell_gaps.extend(gaps)

                cthreads = [threading.Thread(target=cell_client, args=(i,))
                            for i in range(conc)]
                for t in cthreads:
                    t.start()
                for t in cthreads:
                    t.join()
                if cell_errors or not cell_ttfts:
                    matrix[f"serve_{cell}_error"] = "; ".join(cell_errors[:3])
                    continue
                cell_ttfts.sort()
                cell_gaps.sort()

                def pct(sorted_vals, q):
                    return sorted_vals[max(0, int(len(sorted_vals) * q) - 1)]

                matrix[f"serve_{cell}_p50_ttft_ms"] = round(
                    1000 * statistics.median(cell_ttfts), 1)
                matrix[f"serve_{cell}_p95_ttft_ms"] = round(
                    1000 * pct(cell_ttfts, 0.95), 1)
                if cell_gaps:
                    matrix[f"serve_{cell}_p95_itl_ms"] = round(
                        1000 * pct(cell_gaps, 0.95), 1)
    # ---- cached vs cold TTFT (ROADMAP item 5 acceptance): K distinct,
    # never-seen ~1.6k-token system prompts measured COLD (the visit
    # primes the COW prefix cache), then re-visited with fresh user
    # tails — the cached TTFT scales with the cold SUFFIX only, and the
    # session header keeps each pair on one replica (prefix affinity).
    cached_cold: dict = {}
    if os.environ.get("RAY_TPU_BENCH_SKIP_SERVE_CACHED") == "1":
        cached_cold["serve_ttft_cached_skipped"] = True
        cached_cold["serve_ttft_cold_skipped"] = True
    else:
        cold_ttfts: list[float] = []
        cached_ttfts: list[float] = []
        cc_errors: list[str] = []
        cc_samples = int(os.environ.get("RAY_TPU_SERVE_CACHED_SAMPLES", "4"))
        for i in range(cc_samples):
            prefix = (f"[system prompt {i}] "
                      + "You are a terse assistant. Answer carefully. " * 36)
            try:
                t_cold, _, _, _ = one_request(
                    prefix + f"cold tail {i}: " + "wxyz" * 24,
                    session=f"bench-cc-{i}")
                t_cached, _, _, _ = one_request(
                    prefix + f"cached tail {i}: " + "abcd" * 24,
                    session=f"bench-cc-{i}")
            except Exception as e:
                cc_errors.append(f"{type(e).__name__}: {e}")
                continue
            if t_cold is not None:
                cold_ttfts.append(t_cold)
            if t_cached is not None:
                cached_ttfts.append(t_cached)
        if cold_ttfts and cached_ttfts:
            cached_cold["serve_ttft_cold_ms"] = round(
                1000 * statistics.median(cold_ttfts), 1)
            cached_cold["serve_ttft_cached_ms"] = round(
                1000 * statistics.median(cached_ttfts), 1)
        else:
            cached_cold["serve_ttft_cached_skipped"] = True
            cached_cold["serve_ttft_cold_skipped"] = True
            cached_cold["serve_ttft_cached_error"] = "; ".join(cc_errors[:3])
    # Engine prefix-cache effectiveness (ROADMAP item 5): the replica's
    # TRUE-reuse gauge plus the router's affinity hit rate, flushed with
    # the same metrics push as the TTFT histogram polled above.
    prefix_hit_rate = None
    affinity_hit_rate = None
    try:
        from ray_tpu.util.metrics import get_metrics

        time.sleep(6.0)  # one metrics-flusher period: cover the matrix phase
        rows = get_metrics()
        vals = [m["value"] for m in rows
                if m["name"] == "serve_prefix_cache_hit_rate"]
        if vals:
            prefix_hit_rate = round(max(vals), 4)
        aff = [m["value"] for m in rows
               if m["name"] == "serve_prefix_affinity_hit_rate"]
        if aff:
            affinity_hit_rate = round(max(aff), 4)
    except Exception as e:
        print(f"prefix cache gauge unavailable: {e}", file=sys.stderr)
    serve.shutdown()
    ray_tpu.shutdown()
    if errors or not ttfts:
        raise RuntimeError(f"serve bench failed: {errors[:3]}")
    ttfts.sort()
    return {
        "serve_p50_ttft_ms": round(1000 * statistics.median(ttfts), 1),
        "serve_engine_p50_ttft_ms": engine_ttft_p50,
        "serve_p95_ttft_ms": round(1000 * ttfts[max(0, int(len(ttfts) * 0.95) - 1)], 1),
        "serve_ttft_unloaded_ms": (
            round(1000 * statistics.median(ttft_unloaded), 1)
            if ttft_unloaded else None),
        "serve_tokens_per_sec": round(sum(token_counts) / wall, 1),
        "serve_requests": len(token_counts),
        "serve_concurrency": n_clients,
        "serve_decode_steps_per_dispatch": decode_k,
        "serve_preset": preset,
        "serve_prefix_cache_hit_rate": prefix_hit_rate,
        "serve_prefix_affinity_hit_rate": affinity_hit_rate,
        **cached_cold,
        **matrix,
    }


def main() -> None:
    fw = run_framework()
    try:
        raw = run_raw()
    except Exception as e:
        print(f"raw comparison failed: {e}", file=sys.stderr)
        raw = None
    try:
        serve_metrics = run_serve_bench()
    except Exception as e:
        print(f"serve bench failed: {e}", file=sys.stderr)
        serve_metrics = {"serve_error": f"{type(e).__name__}: {e}",
                         "serve_start_failure": _serve_failure_details()}
        try:
            import ray_tpu
            from ray_tpu import serve

            serve.shutdown()
            ray_tpu.shutdown()
        except Exception:
            pass
    # Secondary perf point at the 8B north-star SHAPES (head_dim 128,
    # hidden 4096; 8 layers so params+optimizer fit one chip — MFU is
    # computed from this exact config, so it is the honest per-layer
    # number for Llama-3-8B).
    extra_8b: dict = {}
    if os.environ.get("RAY_TPU_BENCH_SKIP_8B") != "1":
        try:
            from ray_tpu.models.llama import PRESETS as _P, train_flops_per_token

            raw8 = run_raw(preset="llama3-8b-proxy", batch=4)
            flops8 = train_flops_per_token(_P["llama3-8b-proxy"], SEQ)
            extra_8b = {
                "train_tok_s_8b_proxy": round(raw8, 1),
                "mfu_8b_proxy": round(raw8 * flops8 / 197e12, 4),
            }
        except Exception as e:
            print(f"8b-proxy bench failed: {e}", file=sys.stderr)
            extra_8b = {"8b_proxy_error": f"{type(e).__name__}: {e}"}
    extra_longctx: dict = {}
    if os.environ.get("RAY_TPU_BENCH_SKIP_LONGCTX") != "1" and not ALLOW_CPU:
        try:
            extra_longctx = run_longctx()
        except Exception as e:
            print(f"longctx bench failed: {e}", file=sys.stderr)
            extra_longctx = {"longctx_error": f"{type(e).__name__}: {e}"}
    extra_paged: dict = {}
    if os.environ.get("RAY_TPU_BENCH_SKIP_PAGED") != "1" and not ALLOW_CPU:
        try:
            extra_paged = run_paged_bench()
        except Exception as e:
            print(f"paged decode bench failed: {e}", file=sys.stderr)
            extra_paged = {"paged_bench_error": f"{type(e).__name__}: {e}"}
    extra_core: dict = {}
    if os.environ.get("RAY_TPU_BENCH_SKIP_CORE") != "1":
        try:
            extra_core = run_core_bench()
        except Exception as e:
            print(f"core bench failed: {e}", file=sys.stderr)
            extra_core = {"core_bench_error": f"{type(e).__name__}: {e}"}
            try:
                import ray_tpu

                ray_tpu.shutdown()
            except Exception:
                pass
    extra_core_scale: dict = {}
    if os.environ.get("RAY_TPU_BENCH_SKIP_CORE_SCALE") == "1":
        # Declared skip: bench_check reports the core_scale_* cells as
        # intentionally skipped instead of silently vanished.
        extra_core_scale = {"core_scale_skipped": True}
    else:
        try:
            from ray_tpu._core_scale_bench import run_core_scale_bench

            extra_core_scale = run_core_scale_bench(chaos=True)
        except Exception as e:
            print(f"core scale bench failed: {e}", file=sys.stderr)
            extra_core_scale = {
                "core_scale_bench_error": f"{type(e).__name__}: {e}",
                "core_scale_skipped": True,
            }
            try:
                import ray_tpu

                ray_tpu.shutdown()
            except Exception:
                pass
    extra_dag: dict = {}
    if os.environ.get("RAY_TPU_BENCH_SKIP_DAG") != "1":
        try:
            extra_dag = run_dag_bench()
        except Exception as e:
            print(f"dag bench failed: {e}", file=sys.stderr)
            extra_dag = {"dag_bench_error": f"{type(e).__name__}: {e}"}
            try:
                import ray_tpu

                ray_tpu.shutdown()
            except Exception:
                pass
    extra_recovery: dict = {}
    if os.environ.get("RAY_TPU_BENCH_SKIP_RECOVERY") != "1":
        try:
            extra_recovery = run_recovery_bench()
        except Exception as e:
            print(f"recovery bench failed: {e}", file=sys.stderr)
            extra_recovery = {
                "recovery_bench_error": f"{type(e).__name__}: {e}",
                "recovery_train_resume_s_skipped": True,
                "recovery_serve_reroute_s_skipped": True,
                "recovery_ckpt_lag_steps_skipped": True,
            }
            try:
                import ray_tpu

                ray_tpu.shutdown()
            except Exception:
                pass
    extra_overload: dict = {}
    if os.environ.get("RAY_TPU_BENCH_SKIP_OVERLOAD") != "1":
        try:
            extra_overload = run_overload_bench()
        except Exception as e:
            print(f"overload bench failed: {e}", file=sys.stderr)
            extra_overload = {
                "overload_bench_error": f"{type(e).__name__}: {e}",
                "serve_goodput_frac_skipped": True,
                "serve_shed_fast_fail_p95_ms_skipped": True,
                "serve_admitted_p95_ttft_ms_skipped": True,
            }
            try:
                import ray_tpu
                from ray_tpu import serve

                serve.shutdown()
                ray_tpu.shutdown()
            except Exception:
                pass
    extra_migration: dict = {}
    if os.environ.get("RAY_TPU_BENCH_SKIP_MIGRATION") != "1":
        try:
            extra_migration = run_migration_bench()
        except Exception as e:
            print(f"migration bench failed: {e}", file=sys.stderr)
            extra_migration = {
                "migration_bench_error": f"{type(e).__name__}: {e}",
                "serve_ttft_migrated_skipped": True,
                "kv_migration_mb_s_skipped": True,
            }
            try:
                import ray_tpu

                ray_tpu.shutdown()
            except Exception:
                pass
    extra_train_loop: dict = {}
    try:
        from ray_tpu._train_loop_bench import run_train_loop_bench

        # Emits its own *_skipped markers under
        # RAY_TPU_BENCH_SKIP_TRAIN_LOOP=1, so skipped cells are always
        # declared rather than silently vanishing.
        extra_train_loop = run_train_loop_bench()
    except Exception as e:
        print(f"train loop bench failed: {e}", file=sys.stderr)
        extra_train_loop = {
            "train_loop_bench_error": f"{type(e).__name__}: {e}",
            "train_mfu_skipped": True,
            "train_step_dispatch_overhead_skipped": True,
            "train_ckpt_overlap_frac_skipped": True,
        }
        try:
            import ray_tpu

            ray_tpu.shutdown()
        except Exception:
            pass
    extra_tenancy: dict = {}
    try:
        from ray_tpu._tenancy_bench import run_tenancy_bench

        # Returns *_skipped markers itself when
        # RAY_TPU_BENCH_SKIP_TENANCY=1, so skipped cells are always
        # declared rather than silently vanishing.
        extra_tenancy = run_tenancy_bench()
    except Exception as e:
        print(f"tenancy bench failed: {e}", file=sys.stderr)
        extra_tenancy = {
            "tenancy_bench_error": f"{type(e).__name__}: {e}",
            "tenant_quiet_p95_ttft_ms_skipped": True,
            "tenant_goodput_frac_skipped": True,
            "tenant_mixed_batch_parity_skipped": True,
            "tenant_mixed_dispatch_parity_skipped": True,
            "adapter_hot_load_ms_skipped": True,
        }
        try:
            import ray_tpu
            from ray_tpu import serve

            serve.shutdown()
            ray_tpu.shutdown()
        except Exception:
            pass
    extra_fleet: dict = {}
    try:
        from ray_tpu._fleet_bench import run_fleet_bench

        # Returns *_skipped markers itself when
        # RAY_TPU_BENCH_SKIP_FLEET=1, so skipped cells are always
        # declared rather than silently vanishing.
        extra_fleet = run_fleet_bench()
    except Exception as e:
        print(f"fleet bench failed: {e}", file=sys.stderr)
        extra_fleet = {
            "fleet_bench_error": f"{type(e).__name__}: {e}",
            "fleet_skipped": True,
            "serve_replica_cold_start_s_skipped": True,
            "serve_replica_promote_s_skipped": True,
            "serve_replica_promote_speedup_skipped": True,
        }
        try:
            import ray_tpu
            from ray_tpu import serve

            serve.shutdown()
            ray_tpu.shutdown()
        except Exception:
            pass
    extra_speculative: dict = {}
    try:
        from ray_tpu._speculative_bench import run_speculative_bench

        # Returns *_skipped markers itself when
        # RAY_TPU_BENCH_SKIP_SPECULATIVE=1, so skipped cells are always
        # declared rather than silently vanishing.
        extra_speculative = run_speculative_bench()
    except Exception as e:
        print(f"speculative bench failed: {e}", file=sys.stderr)
        extra_speculative = {
            "speculative_bench_error": f"{type(e).__name__}: {e}",
            "decode_tok_s_plain_skipped": True,
            "decode_tok_s_speculative_skipped": True,
            "spec_accept_rate_skipped": True,
            "spec_tokens_per_dispatch_skipped": True,
            "spec_parity_skipped": True,
        }
    value = fw["tokens_per_sec_per_chip"]
    baseline = None
    if os.path.exists("BENCH_BASELINE.json"):
        try:
            baseline = json.load(open("BENCH_BASELINE.json")).get("value")
        except Exception:
            baseline = None
    result = {
        "metric": f"train_tokens_per_sec_per_chip_{PRESET.replace('-', '_')}",
        "value": round(value, 2),
        "unit": "tokens/s/chip",
        "vs_baseline": round(value / baseline, 4) if baseline else 1.0,
        "mfu": round(fw["mfu"], 4),
        "loss": round(fw["loss"], 4),
        "peak_hbm_used_bytes": fw.get("peak_hbm_used_bytes"),
        "peak_object_store_bytes": fw.get("peak_object_store_bytes"),
        "raw_tokens_per_sec": round(raw, 2) if raw else None,
        "framework_overhead_pct": round(100 * (1 - value / raw), 2) if raw else None,
        **serve_metrics,
        **extra_8b,
        **extra_longctx,
        **extra_paged,
        **extra_core,
        **extra_core_scale,
        **extra_dag,
        **extra_recovery,
        **extra_overload,
        **extra_train_loop,
        **extra_tenancy,
        **extra_fleet,
        **extra_speculative,
        # Last: the migration bench's 2k-cell cold TTFT supersedes the
        # serve bench's ~1.6k-prompt cold cell under the same key, so
        # migrated-vs-cold always compares within ONE harness.
        **extra_migration,
    }
    print(json.dumps(result))
    # Regression guard against the most recent recorded round: report-only
    # here (stderr) — CI runs `python -m ray_tpu.bench_check OLD NEW` for
    # the gating exit code.
    try:
        from ray_tpu import bench_check

        prev = os.environ.get("RAY_TPU_BENCH_CHECK_AGAINST") \
            or bench_check.latest_bench_json()
        if prev:
            report = bench_check.compare(bench_check.load_metrics(prev), result)
            print(bench_check.format_report(report, prev, "this run"),
                  file=sys.stderr)
    except Exception as e:
        print(f"bench_check skipped: {e}", file=sys.stderr)


if __name__ == "__main__":
    main()
