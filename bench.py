"""Headline benchmark: Llama train-step throughput on the local TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

North star (BASELINE.json) is Ray Train tokens/sec/chip on Llama-3 — the
reference has no TPU number, so this establishes the baseline; vs_baseline
is reported against the value recorded in BENCH_BASELINE.json if present
(else 1.0).
"""

from __future__ import annotations

import functools
import json
import os
import time

import jax
import jax.numpy as jnp


def main() -> None:
    import optax

    from ray_tpu.models import PRESETS, init_params, loss_fn
    from ray_tpu.parallel import MeshConfig, create_mesh
    from ray_tpu.parallel.sharding import shard_params
    from ray_tpu.models import param_axes

    n_dev = len(jax.devices())
    mesh = create_mesh(MeshConfig(dp=n_dev))
    cfg = PRESETS["llama3-1b"]
    batch_per_chip, seq = 8, 2048

    params = init_params(cfg, jax.random.PRNGKey(0))
    params = shard_params(params, param_axes(cfg), mesh)
    opt = optax.adafactor(1e-3)
    opt_state = jax.jit(opt.init)(params)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (batch_per_chip * n_dev, seq), 0, cfg.vocab_size
    )
    batch = {"tokens": tokens}

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def train_step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(
            lambda p: loss_fn(p, batch, cfg, mesh=mesh)
        )(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    # warmup / compile. NOTE: under the axon tunnel block_until_ready is a
    # no-op; device_get is the only reliable completion fence, so the loss
    # scalar is fetched to host to close each timing region.
    for _ in range(2):
        params, opt_state, loss = train_step(params, opt_state, batch)
    float(jax.device_get(loss))

    steps = 10
    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt_state, loss = train_step(params, opt_state, batch)
    float(jax.device_get(loss))
    dt = time.perf_counter() - t0

    tokens_per_sec_per_chip = batch_per_chip * seq * steps / dt
    baseline = None
    if os.path.exists("BENCH_BASELINE.json"):
        try:
            baseline = json.load(open("BENCH_BASELINE.json")).get("value")
        except Exception:
            baseline = None
    vs = tokens_per_sec_per_chip / baseline if baseline else 1.0
    print(json.dumps({
        "metric": "train_tokens_per_sec_per_chip_llama3_1b",
        "value": round(tokens_per_sec_per_chip, 2),
        "unit": "tokens/s/chip",
        "vs_baseline": round(vs, 4),
    }))


if __name__ == "__main__":
    main()
